"""StreamingDisruptionState: delta-applied disruption snapshots (ISSUE 14).

Every test enforces ONE contract: a disruption pass served from the
persistent streaming state (cached snapshot layers, cached candidate rows,
columnar budgets) produces decisions bit-identical to a cold
`DisruptionSnapshot` + `helpers.get_candidates` +
`build_disruption_budget_mapping` rebuild of the same cluster — across
every row of the invalidation matrix (disruption/stream.py module
docstring) and under a seeded churn stream interleaving pod churn, node
churn, PDB edits, nodepool edits, nominations and deletion marks.
"""

import random

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (LabelSelector, ObjectMeta, Pod,
                                       PodSpec)
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.disruption import methods as methods_mod
from karpenter_tpu.disruption.helpers import (build_disruption_budget_mapping,
                                              get_candidates)
from karpenter_tpu.disruption.methods import (Drift, Emptiness,
                                              MultiNodeConsolidation,
                                              SingleNodeConsolidation)
from karpenter_tpu.disruption.prefix import DisruptionSnapshot

from expectations import (OD, SPOT, bind_pod, catalog,
                          consolidation_nodepool, make_env,
                          make_nodeclaim_and_node)

pytestmark = pytest.mark.churn


def summarize(cmd, results=None):
    return {
        "decision": cmd.decision,
        "candidates": [c.name for c in cmd.candidates],
        "replacements": [[it.name for it in r.instance_type_options]
                         for r in cmd.replacements],
    }


METHODS = (Emptiness, Drift, MultiNodeConsolidation, SingleNodeConsolidation)


def make_method(env, cls):
    if cls in (MultiNodeConsolidation, SingleNodeConsolidation):
        return cls(env.cluster, env.provisioner, spot_to_spot_enabled=False,
                   clock=env.clock, recorder=env.recorder)
    return cls(env.cluster, env.provisioner, recorder=env.recorder)


def cold_pass(env, cls, disrupting=()):
    """The oracle: a fresh snapshot + the cold candidate/budget path."""
    m = make_method(env, cls)
    snap = DisruptionSnapshot(env.cluster, env.provisioner)
    if hasattr(m, "attach_snapshot"):
        m.attach_snapshot(snap)
    cands = get_candidates(env.cluster, env.provisioner, m.should_disrupt,
                           disrupting_provider_ids=disrupting,
                           disruption_class=m.disruption_class,
                           context=snap)
    budgets = build_disruption_budget_mapping(env.cluster, m.reason)
    cmd, res = m.compute_command(budgets, cands)
    return [c.name for c in cands], budgets, summarize(cmd, res)


def stream_pass(env, cls, disrupting=()):
    """The streaming path, THROUGH the controller-owned persistent state."""
    stream = env.disruption.stream
    m = make_method(env, cls)
    snap = stream.refresh(env.cluster, env.provisioner)
    if hasattr(m, "attach_snapshot"):
        m.attach_snapshot(snap)
    cands = stream.candidates_for(m.should_disrupt,
                                  disrupting_provider_ids=disrupting,
                                  disruption_class=m.disruption_class)
    budgets = stream.budget_mapping(m.reason)
    cmd, res = m.compute_command(budgets, cands)
    return [c.name for c in cands], budgets, summarize(cmd, res)


def assert_parity(env, disrupting=(), methods=METHODS):
    for cls in methods:
        got = stream_pass(env, cls, disrupting)
        want = cold_pass(env, cls, disrupting)
        assert got == want, (cls.__name__, got, want)


def small_fleet(n=6, pods_per_node=(1, 1, 2, 0, 1, 1)):
    env = make_env()
    its = sorted(catalog(), key=lambda it: it.name)
    nodes = []
    for i in range(n):
        it = its[i % 7]
        cores = max(1, it.capacity.get("cpu", 4000) // 1000)
        nc, node = make_nodeclaim_and_node(
            env, capacity_type=OD if i % 3 else SPOT, instance_type=it,
            allocatable={"cpu": str(cores), "memory": "16Gi", "pods": "110"})
        for _ in range(pods_per_node[i % len(pods_per_node)]):
            bind_pod(env, node, cpu="100m", memory="128Mi",
                     labels={"app": "web"})
        nodes.append((nc, node))
    env.clock.step(600)
    env.settle(rounds=1)
    return env, nodes


class TestInvalidationMatrix:
    """One directed vector per matrix row: the reused/rebuilt layer split
    is what the row promises, and decisions stay equal to cold."""

    def test_idle_pass_reuses_every_layer(self):
        env, _ = small_fleet()
        stream = env.disruption.stream
        stream.refresh(env.cluster, env.provisioner)
        snap1 = stream._snapshot
        enc_map = snap1._encodings
        stream.refresh(env.cluster, env.provisioner)
        assert stream._snapshot is snap1
        assert stream.last["layers"] == {
            "pods": "reused", "context": "reused", "scheduler": "reused",
            "encodings": "reused"}
        assert snap1._encodings is enc_map
        assert stream.last["rows_rebuilt"] == 0
        assert stream.last["rows_reused"] == len(env.cluster.nodes)
        assert_parity(env)

    def test_scheduled_pod_change_rebuilds_pod_layer_and_dirty_row_only(self):
        env, nodes = small_fleet()
        stream = env.disruption.stream
        stream.refresh(env.cluster, env.provisioner)
        bind_pod(env, nodes[2][1], cpu="100m", memory="64Mi")
        env.settle(rounds=1)
        stream.refresh(env.cluster, env.provisioner)
        assert stream.last["layers"]["pods"] == "rebuilt"
        # the bind changed the node's available(): its exist row must
        # re-encode, so the scheduler layer rebuilds — but the encode is
        # delta-applied (only the dirty row, test_node_encode_rows below)
        assert stream.last["layers"]["scheduler"] == "rebuilt"
        # the bind bumped ONE node's revision: exactly one row re-derives
        assert stream.last["rows_rebuilt"] == 1, stream.last
        assert_parity(env)

    def test_pending_pod_arrival_clears_encodings_keeps_rows(self):
        env, _ = small_fleet()
        stream = env.disruption.stream
        stream.refresh(env.cluster, env.provisioner)
        env.store.create(Pod(
            metadata=ObjectMeta(name="pending-1", namespace="default"),
            spec=PodSpec(),
            container_requests=[{"cpu": 100, "memory": 64 * 1000}]))
        stream.refresh(env.cluster, env.provisioner)
        assert stream.last["layers"]["pods"] == "rebuilt"
        assert stream.last["layers"]["encodings"] == "rebuilt"
        assert stream.last["rows_rebuilt"] == 0
        assert_parity(env)

    def test_node_update_rebuilds_its_row_and_scheduler(self):
        env, nodes = small_fleet()
        stream = env.disruption.stream
        stream.refresh(env.cluster, env.provisioner)
        node = nodes[1][1]
        live = env.store.get(type(node), node.metadata.name)
        live.metadata.labels["example.com/extra"] = "yes"
        env.store.update(live)
        env.settle(rounds=1)
        stream.refresh(env.cluster, env.provisioner)
        assert stream.last["layers"]["scheduler"] == "rebuilt"
        assert stream.last["rows_rebuilt"] == 1, stream.last
        assert_parity(env)

    def test_pdb_change_rederives_rows_but_keeps_encodings(self):
        env, _ = small_fleet()
        stream = env.disruption.stream
        snap = stream.refresh(env.cluster, env.provisioner)
        # force an encoding into the memo so "kept" is observable
        m = make_method(env, SingleNodeConsolidation)
        cands = stream.candidates_for(m.should_disrupt)
        assert cands
        snap.simulate(cands[:1])
        enc_keys = set(snap._encodings)
        assert enc_keys
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="block-web", namespace="default"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "web"}),
                         max_unavailable="0")))
        env.settle(rounds=1)
        stream.refresh(env.cluster, env.provisioner)
        assert stream.last["layers"]["context"] == "rebuilt"
        assert stream.last["layers"]["encodings"] == "reused"
        assert set(snap._encodings) == enc_keys
        # every row re-derives its eviction verdict under the new PDB
        assert stream.last["rows_rebuilt"] == len(env.cluster.nodes)
        # and the PDB now blocks: web-bearing nodes are no longer candidates
        assert_parity(env)
        names, _, _ = stream_pass(env, SingleNodeConsolidation)
        blocked = [c.name for c in cands
                   if any(p.metadata.labels.get("app") == "web"
                          for p in c.reschedulable_pods)]
        assert not set(blocked) & set(names)

    def test_nodepool_edit_rebuilds_context_and_scheduler(self):
        env, _ = small_fleet()
        stream = env.disruption.stream
        stream.refresh(env.cluster, env.provisioner)
        pool = env.store.list(type(consolidation_nodepool()))[0]
        from karpenter_tpu.api.nodepool import Budget
        pool.spec.disruption.budgets = [Budget(nodes="1")]
        env.store.update(pool)
        env.settle(rounds=1)
        stream.refresh(env.cluster, env.provisioner)
        assert stream.last["layers"]["context"] == "rebuilt"
        assert stream.last["layers"]["scheduler"] == "rebuilt"
        assert stream.budget_mapping("underutilized") == \
            build_disruption_budget_mapping(env.cluster, "underutilized")
        assert_parity(env)

    def test_nomination_and_deletion_mark_are_live_gates(self):
        env, nodes = small_fleet()
        stream = env.disruption.stream
        stream.refresh(env.cluster, env.provisioner)
        node = nodes[0][1]
        pod = Pod(metadata=ObjectMeta(name="nom", namespace="default"),
                  spec=PodSpec())
        env.cluster.nominate_node_for_pod(node.metadata.name, pod)
        stream.refresh(env.cluster, env.provisioner)
        # no row rebuilt: nomination is a per-pass mask, not cached state
        assert stream.last["rows_rebuilt"] == 0
        assert_parity(env)
        names, _, _ = stream_pass(env, SingleNodeConsolidation)
        assert node.metadata.name not in names
        # expire the nomination, then mark for deletion
        env.clock.step(30)
        sn = next(sn for sn in env.cluster.nodes.values()
                  if sn.name() == node.metadata.name)
        env.cluster.mark_for_deletion(sn.provider_id)
        stream.refresh(env.cluster, env.provisioner)
        names, _, _ = stream_pass(env, SingleNodeConsolidation)
        assert node.metadata.name not in names
        assert_parity(env)
        env.cluster.unmark_for_deletion(sn.provider_id)
        assert_parity(env)

    def test_budget_mapping_matches_cold_mapping_across_reasons(self):
        env, _ = small_fleet()
        stream = env.disruption.stream
        stream.refresh(env.cluster, env.provisioner)
        for reason in ("underutilized", "empty", "drifted"):
            assert stream.budget_mapping(reason) == \
                build_disruption_budget_mapping(env.cluster, reason)

    def test_node_encode_rows_are_delta_applied(self):
        """The scheduler layer rides the stream's ProblemState: a warm
        pass re-encodes ZERO node rows, a single node label change
        re-encodes exactly the dirty row."""
        env, nodes = small_fleet()
        stream = env.disruption.stream
        snap = stream.refresh(env.cluster, env.provisioner)
        m = make_method(env, SingleNodeConsolidation)
        cands = stream.candidates_for(m.should_disrupt)
        snap.simulate(cands)  # forces an encode through build_problem
        first = stream.problem_state.last["node_rows_reencoded"]
        assert first == len(snap.state_nodes)
        # warm: a pending pod invalidates encodings but NOT node rows
        env.store.create(Pod(
            metadata=ObjectMeta(name="warm-pending", namespace="default"),
            spec=PodSpec(),
            container_requests=[{"cpu": 100, "memory": 64 * 1000}]))
        snap = stream.refresh(env.cluster, env.provisioner)
        cands = stream.candidates_for(m.should_disrupt)
        snap.simulate(cands)
        assert stream.problem_state.last["node_rows_reencoded"] == 0
        assert stream.problem_state.last["encode_kind"] == "delta"


SEEDS = list(range(8100, 8106))


@pytest.mark.parametrize("seed", SEEDS)
def test_streaming_churn_fuzzer_matches_cold_every_step(seed):
    """Seeded churn: after EVERY mutation the streaming pass (accumulated
    deltas) must agree with a cold rebuild for all four methods."""
    rng = random.Random(seed)
    env, nodes = small_fleet(n=8)
    its = sorted(catalog(), key=lambda it: it.name)
    assert_parity(env)
    pools = env.store.list(type(consolidation_nodepool()))
    seq = 0
    for step in range(10):
        action = rng.choice(
            ["bind", "unbind", "pending", "add_node", "pdb", "budget",
             "nominate", "mark", "drift"])
        seq += 1
        if action == "bind":
            _, node = rng.choice(nodes)
            if env.store.get(type(node), node.metadata.name) is not None:
                bind_pod(env, node, cpu="100m", memory="64Mi",
                         labels={"app": rng.choice(("web", "api"))})
        elif action == "unbind":
            pods = [p for p in env.store.list(Pod) if p.spec.node_name]
            if pods:
                env.store.delete(rng.choice(pods))
        elif action == "pending":
            env.store.create(Pod(
                metadata=ObjectMeta(name=f"churn-pend-{seed}-{seq}",
                                    namespace="default"),
                spec=PodSpec(),
                container_requests=[{"cpu": 50, "memory": 32 * 1000}]))
        elif action == "add_node":
            it = rng.choice(its[:7])
            cores = max(1, it.capacity.get("cpu", 4000) // 1000)
            nc, node = make_nodeclaim_and_node(
                env, capacity_type=OD, instance_type=it,
                allocatable={"cpu": str(cores), "memory": "16Gi",
                             "pods": "110"})
            nodes.append((nc, node))
            env.clock.step(600)
        elif action == "pdb":
            env.store.create(PodDisruptionBudget(
                metadata=ObjectMeta(name=f"churn-pdb-{seed}-{seq}",
                                    namespace="default"),
                spec=PDBSpec(
                    selector=LabelSelector(
                        match_labels={"app": rng.choice(("web", "api"))}),
                    max_unavailable=rng.choice(("0", "1")))))
        elif action == "budget":
            from karpenter_tpu.api.nodepool import Budget
            pool = rng.choice(pools)
            pool.spec.disruption.budgets = [
                Budget(nodes=rng.choice(("0", "1", "50%", "100%")))]
            env.store.update(pool)
        elif action == "nominate":
            _, node = rng.choice(nodes)
            env.cluster.nominate_node_for_pod(
                node.metadata.name,
                Pod(metadata=ObjectMeta(name=f"nom-{seq}",
                                        namespace="default"),
                    spec=PodSpec()))
        elif action == "mark":
            sn = rng.choice(list(env.cluster.nodes.values()))
            if rng.random() < 0.5:
                env.cluster.mark_for_deletion(sn.provider_id)
            else:
                env.cluster.unmark_for_deletion(sn.provider_id)
        elif action == "drift":
            nc, _ = rng.choice(nodes)
            live = env.store.get(type(nc), nc.name)
            if live is not None:
                live.metadata.annotations[
                    api_labels.NODEPOOL_HASH_ANNOTATION_KEY] = "stale"
                from karpenter_tpu.api.nodepool import NODEPOOL_HASH_VERSION
                live.metadata.annotations[
                    api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = \
                    NODEPOOL_HASH_VERSION
                env.store.update(live)
        env.settle(rounds=1)
        if rng.random() < 0.3:
            env.clock.step(rng.choice((1, 30, 400)))
        assert_parity(env)


def test_controller_pass_uses_streaming_state():
    """End to end through DisruptionController.reconcile: the second pass
    is served warm (rows reused) and still finds the same decision a cold
    controller would."""
    env, nodes = small_fleet()
    env.disruption.reconcile()
    stream = env.disruption.stream
    assert stream.stats["passes"] == 1
    env.disruption.pending = None  # drop any TTL wait; fresh decision
    env.disruption.reconcile()
    assert stream.stats["passes"] == 2
    assert stream.last["rows_reused"] == len(env.cluster.nodes)
    assert stream.last["rows_rebuilt"] == 0


class TestReviewRegressionPins:
    """Pins for the two parity bugs the PR review caught: the pinned
    catalog token must describe the scheduler's OWN pool ordering, and
    the encodings token must see drought-mask TTL expiry."""

    def test_catalog_token_matches_scheduler_pool_order(self):
        """Per-pool instance-type lists + a weight swap: the pinned
        catalog token must be computed over the weight-ordered, IT-less-
        pools-dropped ordering _build_scheduler hands the scheduler —
        _ordered_union is order-sensitive, and a name-ordered token would
        key the device-encoding cache with misaligned IT columns."""
        from expectations import Env
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.provisioning.tensor_scheduler import \
            catalog_cache_token

        its = sorted(catalog(), key=lambda it: it.name)

        class PerPoolProvider(KwokCloudProvider):
            def get_instance_types(self, nodepool):
                if getattr(nodepool, "name", "") == "b-pool":
                    return its[:40]
                return its[20:60]

        env = Env(provider=lambda store: PerPoolProvider(store=store))
        pool_a = consolidation_nodepool(name="a-pool")
        pool_a.spec.weight = 10
        pool_b = consolidation_nodepool(name="b-pool")
        pool_b.spec.weight = 50
        env.store.create(pool_a)
        env.store.create(pool_b)
        for i in range(3):
            _, node = make_nodeclaim_and_node(
                env, capacity_type=OD, instance_type=its[25],
                allocatable={"cpu": "4", "memory": "16Gi", "pods": "110"},
                nodepool="b-pool" if i % 2 else "a-pool")
            bind_pod(env, node, cpu="100m", memory="64Mi")
        env.clock.step(600)
        env.settle(rounds=1)

        stream = env.disruption.stream
        for _ in range(2):  # before and after the weight swap
            snap = stream.refresh(env.cluster, env.provisioner)
            # the structural invariant: the pinned token equals the token
            # of the scheduler's OWN (weight-ordered) pool list
            assert stream._tok["catalog"] == catalog_cache_token(
                snap.nodepools, snap.instance_types_by_pool)
            assert_parity(env)
            pool_a.spec.weight, pool_b.spec.weight = \
                pool_b.spec.weight, pool_a.spec.weight
            env.store.update(pool_a)
            env.store.update(pool_b)
            env.settle(rounds=1)

    def test_drought_mask_ttl_expiry_invalidates_encodings(self):
        """An unavailable-offerings entry whose TTL lapses WITHOUT any
        intervening provisioner reconcile (nothing called expire()) must
        still invalidate the reused encodings: a cold rebuild would prune
        the entry and encode without the mask, and the streaming pass
        must match it (the token reads live(), which prunes)."""
        env, _ = small_fleet()
        stream = env.disruption.stream
        snap = stream.refresh(env.cluster, env.provisioner)
        m = make_method(env, SingleNodeConsolidation)
        cands = stream.candidates_for(m.should_disrupt)
        snap.simulate(cands[:1])  # seed the encoding memo
        env.unavailable.mark(zone="test-zone-a")
        snap = stream.refresh(env.cluster, env.provisioner)
        assert stream.last["layers"]["encodings"] == "rebuilt"
        snap.simulate(cands[:1])
        # the TTL lapses silently: no reconcile, no expire() call
        env.clock.step(400)
        stream.refresh(env.cluster, env.provisioner)
        assert stream.last["layers"]["encodings"] == "rebuilt", (
            "lapsed drought mask kept stale encodings alive")
        assert_parity(env)
