"""Operator wiring, options, events recorder, metrics, aux controllers."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.nodepool import NodePool
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import RepairPolicy
from karpenter_tpu.events.recorder import Event, Recorder
from karpenter_tpu.metrics.registry import REGISTRY, Registry
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import FeatureGates, Options, parse_options
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods


@pytest.fixture
def op():
    return Operator(clock=FakeClock())


def settle(op, rounds=6):
    for _ in range(rounds):
        op.step()
        op.clock.step(1.1)
    assert op.step(), "operator did not quiesce"


class TestOperator:
    def test_full_wiring_provisions(self, op):
        op.store.create(make_nodepool(name="default"))
        for p in make_pods(4, cpu="500m"):
            op.store.create(p)
        settle(op)
        assert all(p.spec.node_name for p in op.store.list(Pod))
        assert op.store.list(Node)

    def test_nodepool_hash_annotation_maintained(self, op):
        pool = make_nodepool(name="default")
        op.store.create(pool)
        op.step()
        assert pool.metadata.annotations[
            api_labels.NODEPOOL_HASH_ANNOTATION_KEY] == pool.static_hash()

    def test_nodepool_counter_aggregates(self, op):
        pool = make_nodepool(name="default")
        op.store.create(pool)
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        assert pool.status.resources.get("nodes") == 1000
        assert pool.status.resources.get("cpu", 0) > 0

    def test_expiration_deletes_old_claims(self, op):
        pool = make_nodepool(name="default")
        pool.spec.template.spec.expire_after = 3600.0
        op.store.create(pool)
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        assert len(op.store.list(NodeClaim)) == 1
        op.clock.step(3700)
        settle(op)
        # claim expired; replacement provisioned for the rescheduled pod
        for p in op.store.list(Pod):
            assert p.spec.node_name
        claims = op.store.list(NodeClaim)
        assert all(op.clock.now() -
                   c.metadata.creation_timestamp < 3600 for c in claims)

    def test_garbage_collection_removes_vanished_instances(self, op):
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        nc = op.store.list(NodeClaim)[0]
        # instance vanishes behind karpenter's back
        del op.cloud_provider.created[nc.status.provider_id]
        settle(op)  # gc singleton runs as part of step()
        assert op.store.get(NodeClaim, nc.name) is None

    def test_metrics_exposed(self, op):
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        text = op.metrics_text()
        assert "karpenter_nodeclaims_created_total" in text
        assert "karpenter_provisioner_scheduling_duration_seconds_count" in text
        assert "karpenter_pods_bound_duration_seconds" in text
        assert "karpenter_nodes_allocatable" in text


class TestNodeRepair:
    def test_unhealthy_node_repaired(self):
        class RepairingKwok(KwokCloudProvider):
            def repair_policies(self):
                return [RepairPolicy(condition_type="Ready",
                                     condition_status="False",
                                     toleration_duration=300.0)]

        clock = FakeClock()
        op = Operator(options=Options(feature_gates="NodeRepair"),
                      cloud_provider=RepairingKwok(), clock=clock)
        op.cloud_provider.store = op.store
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        node = op.store.list(Node)[0]
        from karpenter_tpu.utils.node import set_condition
        set_condition(node, "Ready", "False", now=clock.now())
        op.store.update(node)
        clock.step(301)
        settle(op)
        # node force-deleted and replaced; pod rescheduled
        live = op.store.list(Node)
        assert all(n.name != node.name for n in live)
        for p in op.store.list(Pod):
            assert p.spec.node_name


class TestOptions:
    def test_env_fallback(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_BATCH_IDLE_DURATION", "2.5")
        opts = parse_options([])
        assert opts.batch_idle_duration == 2.5

    def test_flag_wins(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_LOG_LEVEL", "debug")
        opts = parse_options(["--log-level", "error"])
        assert opts.log_level == "error"

    def test_feature_gates(self):
        fg = FeatureGates.parse("SpotToSpotConsolidation=true,NodeRepair")
        assert fg.spot_to_spot_consolidation and fg.node_repair
        assert not FeatureGates.parse("").node_repair


class TestRecorder:
    def test_dedupes_identical_events(self):
        clock = FakeClock()
        r = Recorder(clock)
        ev = lambda: Event(object_kind="Node", object_name="n1",
                           type="Normal", reason="Test", message="hi")
        r.publish(ev())
        r.publish(ev())
        assert len(r.events) == 1
        clock.step(121)
        r.publish(ev())
        assert len(r.events) == 2

    def test_different_messages_pass(self):
        r = Recorder(FakeClock())
        r.publish(Event("Node", "n1", "Normal", "Test", "a"))
        r.publish(Event("Node", "n1", "Normal", "Test", "b"))
        assert len(r.events) == 2


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = Registry()
        c = reg.counter("test_total", "t", ("l",))
        c.inc({"l": "x"})
        c.inc({"l": "x"}, 2)
        assert c.value({"l": "x"}) == 3
        g = reg.gauge("test_gauge", "t")
        g.set(7.5)
        assert g.value() == 7.5
        h = reg.histogram("test_seconds", "t")
        h.observe(0.05)
        h.observe(3.0)
        assert h.count() == 2
        text = reg.expose()
        assert 'test_total{l="x"} 3' in text
        assert "test_seconds_bucket" in text


class TestBooleanFlags:
    """Flags must always mean what they say; env only moves the default
    (ADVICE round-1: store_false flip made --enable-profiling disable when
    KARPENTER_ENABLE_PROFILING=true)."""

    def test_flag_agrees_with_env(self, monkeypatch):
        from karpenter_tpu.operator.options import parse_options
        monkeypatch.setenv("KARPENTER_ENABLE_PROFILING", "true")
        assert parse_options(["--enable-profiling"]).enable_profiling is True
        assert parse_options([]).enable_profiling is True

    def test_no_flag_disables(self, monkeypatch):
        from karpenter_tpu.operator.options import parse_options
        monkeypatch.setenv("KARPENTER_ENABLE_PROFILING", "true")
        assert parse_options(["--no-enable-profiling"]).enable_profiling \
            is False


class TestConsistencyTaintCheck:
    def test_missing_taint_publishes_event(self):
        from karpenter_tpu.api.nodeclaim import (COND_INITIALIZED,
                                                 COND_LAUNCHED,
                                                 COND_REGISTERED, NodeClaim,
                                                 NodeClaimSpec)
        from karpenter_tpu.api.objects import (Node, NodeSpec, NodeStatus,
                                               ObjectMeta, Taint)
        from karpenter_tpu.controllers.nodeclaim_aux import Consistency
        from karpenter_tpu.events.recorder import Recorder
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock
        from karpenter_tpu.utils import resources as res

        clock = FakeClock()
        store = Store(clock)
        recorder = Recorder(clock)
        alloc = res.parse_list({"cpu": "4"})
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""),
                       spec=NodeClaimSpec(
                           taints=[Taint(key="dedicated", value="x",
                                         effect="NoSchedule")]))
        nc.status.node_name = "n1"
        nc.status.allocatable = dict(alloc)
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED):
            nc.conditions.set_true(cond)
        store.create(nc)
        store.create(Node(metadata=ObjectMeta(name="n1", namespace=""),
                          spec=NodeSpec(),  # taint missing on the node
                          status=NodeStatus(capacity=dict(alloc),
                                            allocatable=dict(alloc))))
        Consistency(store, recorder, clock).reconcile(store.get(NodeClaim, "nc1"))
        msgs = [e.message for e in recorder.for_object("nc1")]
        assert any("taint" in m for m in msgs), msgs


class TestManagerTimerDedup:
    def test_requeue_coalesces_per_object(self):
        """workqueue AddAfter dedup: repeated requeue_after results from
        event-driven reconciles must keep ONE pending timer per
        (controller, object) — the earliest — not spawn a chain per event."""
        from karpenter_tpu.controllers.manager import Controller, Manager, Result
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock)
        fired = []

        class Periodic(Controller):
            name = "test.periodic"
            kinds = (Pod,)

            def reconcile(self, obj):
                fired.append(clock.now())
                return Result(requeue_after=300.0)

        mgr = Manager(store, clock)
        mgr.register(Periodic())
        pod = make_pod(cpu="100m")
        store.create(pod)
        mgr.drain()
        # a burst of unrelated events re-reconciles the pod repeatedly
        for _ in range(5):
            store.update(pod)
            mgr.drain()
        assert len(mgr._timer_pending) == 1
        n = len(fired)
        clock.step(301)
        mgr.drain()
        assert len(fired) == n + 1          # ONE timer fired, not six
        assert len(mgr._timer_pending) == 1  # and it rearmed exactly once

    def test_earlier_requeue_wins(self):
        from karpenter_tpu.controllers.manager import Controller, Manager, Result
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock)
        delays = iter([300.0, 5.0])
        fired = []

        class C(Controller):
            name = "test.varying"
            kinds = (Pod,)

            def reconcile(self, obj):
                fired.append(clock.now())
                return Result(requeue_after=next(delays, None))

        mgr = Manager(store, clock)
        mgr.register(C())
        pod = make_pod(cpu="100m")
        store.create(pod)
        mgr.drain()          # schedules +300
        store.update(pod)
        mgr.drain()          # schedules +5 -> fires first; +300 stays pending
        clock.step(6)
        mgr.drain()
        assert len(fired) == 3  # the 5s timer fired without waiting out 300s

    def test_later_requeue_not_dropped(self):
        """A later AddAfter must still fire even when an earlier timer is
        pending (client-go delivers every AddAfter time; dedup happens at
        queue insertion, not by discarding delays) — otherwise a controller
        relying on a later periodic recheck silently misses it."""
        from karpenter_tpu.controllers.manager import Controller, Manager, Result
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock)
        delays = iter([5.0, 300.0])
        fired = []

        class C(Controller):
            name = "test.later"
            kinds = (Pod,)

            def reconcile(self, obj):
                fired.append(clock.now())
                return Result(requeue_after=next(delays, None))

        mgr = Manager(store, clock)
        mgr.register(C())
        pod = make_pod(cpu="100m")
        store.create(pod)
        mgr.drain()          # schedules +5
        store.update(pod)
        mgr.drain()          # schedules +300 — must NOT be dropped
        clock.step(6)
        mgr.drain()          # +5 fires; reconcile returns no new requeue
        assert len(fired) == 3
        clock.step(300)
        mgr.drain()          # the later +300 intent still fires
        assert len(fired) == 4

    def test_latest_intent_survives_multiple_displacements(self):
        """Periodic recheck +300, then retry backoffs +5 and +1: the
        earliest fires first and the LATEST intent (the periodic recheck)
        must still fire even after two displacements; the sandwiched +5 is
        subsumed by the +1 reconcile, which saw newer state."""
        from karpenter_tpu.controllers.manager import Controller, Manager, Result
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock)
        delays = iter([300.0, 5.0, 1.0])
        fired = []

        class C(Controller):
            name = "test.displaced"
            kinds = (Pod,)

            def reconcile(self, obj):
                fired.append(clock.now())
                return Result(requeue_after=next(delays, None))

        mgr = Manager(store, clock)
        mgr.register(C())
        pod = make_pod(cpu="100m")
        store.create(pod)
        mgr.drain()          # schedules +300
        store.update(pod)
        mgr.drain()          # schedules +5 (displaces the +300 to deferred)
        store.update(pod)
        mgr.drain()          # schedules +1 (the +300 stays deferred)
        clock.step(2)
        mgr.drain()          # +1 fires; reconcile returns no requeue
        assert len(fired) == 4
        clock.step(300)
        mgr.drain()          # the +300 periodic recheck still fires
        assert len(fired) == 5
        # bounded: at most one live + one deferred timer per object, ever
        assert len(mgr._timer_pending) <= 1
        assert len(mgr._timer_deferred) <= 1


class TestExpirationSuite:
    """expiration/suite_test.go:149-199."""

    def _env(self):
        from karpenter_tpu.controllers.nodeclaim_aux import Expiration
        from karpenter_tpu.kube.store import Store
        clock = FakeClock()
        store = Store(clock)
        return store, clock, Expiration(store, clock)

    def _claim(self, store, expire_after):
        from karpenter_tpu.api.nodeclaim import NodeClaim
        from karpenter_tpu.api.objects import ObjectMeta
        nc = NodeClaim(metadata=ObjectMeta(name="exp-1", namespace=""))
        nc.spec.expire_after = expire_after
        store.create(nc)
        return nc

    def test_disabled_expiration_never_removes(self):
        store, clock, ctrl = self._env()
        nc = self._claim(store, None)  # Never
        clock.step(10**6)
        assert ctrl.reconcile(nc) is None
        from karpenter_tpu.api.nodeclaim import NodeClaim
        assert store.get(NodeClaim, "exp-1", "") is not None

    def test_non_expired_claim_kept_with_requeue_at_expiry(self):
        store, clock, ctrl = self._env()
        nc = self._claim(store, 300.0)
        clock.step(100)
        result = ctrl.reconcile(nc)
        from karpenter_tpu.api.nodeclaim import NodeClaim
        assert store.get(NodeClaim, "exp-1", "") is not None
        # requeue lands exactly at the remaining lifetime
        assert result is not None and abs(result.requeue_after - 200.0) < 1.0

    def test_expired_claim_deleted(self):
        store, clock, ctrl = self._env()
        nc = self._claim(store, 300.0)
        clock.step(301)
        ctrl.reconcile(nc)
        from karpenter_tpu.api.nodeclaim import NodeClaim
        assert store.get(NodeClaim, "exp-1", "") is None

    def test_already_deleting_claim_not_expired_again(self):
        """expiration/suite_test.go:181-199."""
        store, clock, ctrl = self._env()
        nc = self._claim(store, 300.0)
        nc.metadata.finalizers.append("karpenter.sh/termination")
        clock.step(301)
        ctrl.reconcile(nc)   # starts deletion (finalizer holds the object)
        assert nc.metadata.deletion_timestamp is not None
        stamped = nc.metadata.deletion_timestamp
        clock.step(50)
        assert ctrl.reconcile(nc) is None  # no re-delete / no restamp
        assert nc.metadata.deletion_timestamp == stamped


class TestGarbageCollectionSuite:
    """garbagecollection/suite_test.go: both sweep directions."""

    def _env(self):
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.nodeclaim_aux import GarbageCollection
        from karpenter_tpu.kube.store import Store
        clock = FakeClock()
        store = Store(clock)
        provider = KwokCloudProvider(store=store)
        return store, clock, provider, GarbageCollection(store, provider, clock)

    def test_claim_with_vanished_instance_deleted(self):
        from karpenter_tpu.api import labels as api_labels
        from karpenter_tpu.api.nodeclaim import COND_LAUNCHED, NodeClaim
        from karpenter_tpu.api.objects import ObjectMeta
        store, clock, provider, gc_ctrl = self._env()
        nc = NodeClaim(metadata=ObjectMeta(
            name="gc-1", namespace="",
            labels={api_labels.LABEL_INSTANCE_TYPE: "c-1x-amd64-linux"}))
        provider.create(nc)
        nc.conditions.set_true(COND_LAUNCHED, reason="Launched")
        store.create(nc)
        # instance vanishes out from under the claim (manual console delete)
        del provider.created[nc.status.provider_id]
        gc_ctrl.reconcile()
        assert store.get(NodeClaim, "gc-1", "") is None

    def test_untracked_instance_reaped(self):
        from karpenter_tpu.api import labels as api_labels
        from karpenter_tpu.api.nodeclaim import NodeClaim
        from karpenter_tpu.api.objects import ObjectMeta
        store, clock, provider, gc_ctrl = self._env()
        ghost = NodeClaim(metadata=ObjectMeta(
            name="ghost", namespace="",
            labels={api_labels.LABEL_INSTANCE_TYPE: "c-1x-amd64-linux"}))
        provider.create(ghost)  # instance exists, claim never stored
        assert len(provider.list()) == 1
        gc_ctrl.reconcile()
        assert provider.list() == []

    def test_matched_pairs_left_alone(self):
        from karpenter_tpu.api import labels as api_labels
        from karpenter_tpu.api.nodeclaim import COND_LAUNCHED, NodeClaim
        from karpenter_tpu.api.objects import ObjectMeta
        store, clock, provider, gc_ctrl = self._env()
        nc = NodeClaim(metadata=ObjectMeta(
            name="ok-1", namespace="",
            labels={api_labels.LABEL_INSTANCE_TYPE: "c-1x-amd64-linux"}))
        provider.create(nc)
        nc.conditions.set_true(COND_LAUNCHED, reason="Launched")
        store.create(nc)
        gc_ctrl.reconcile()
        assert store.get(NodeClaim, "ok-1", "") is not None
        assert len(provider.list()) == 1


class TestPodEventsSuite:
    """podevents/controller.go:63-98: lastPodEventTime with 5 s dedupe."""

    def test_pod_event_stamps_with_dedupe(self):
        from karpenter_tpu.api.nodeclaim import NodeClaim
        from karpenter_tpu.api.objects import ObjectMeta
        from karpenter_tpu.controllers.nodeclaim_aux import PodEvents
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.state.cluster import Cluster
        clock = FakeClock()
        store = Store(clock)
        cluster = Cluster(store, clock)
        ctrl = PodEvents(store, cluster, clock)
        nc = NodeClaim(metadata=ObjectMeta(name="pe-1", namespace=""))
        nc.status.node_name = "n1"
        store.create(nc)
        pod = make_pod()
        pod.spec.node_name = "n1"
        store.create(pod)
        clock.step(10)
        ctrl.reconcile(pod)
        t1 = nc.status.last_pod_event_time
        assert t1 == clock.now()
        clock.step(2)  # inside the dedupe window
        ctrl.reconcile(pod)
        assert nc.status.last_pod_event_time == t1
        clock.step(4)  # past it
        ctrl.reconcile(pod)
        assert nc.status.last_pod_event_time == clock.now()


class TestHydration:
    """nodeclaim/node hydration: objects from older versions get current
    invariant fields backfilled."""

    def test_nodeclaim_hydrated_with_pool_label_and_finalizer(self):
        from karpenter_tpu.api.objects import ObjectMeta, OwnerReference
        from karpenter_tpu.controllers.hydration import NodeClaimHydration
        from karpenter_tpu.kube.store import Store
        store = Store(FakeClock())
        nc = NodeClaim(metadata=ObjectMeta(
            name="old-nc", namespace="",
            owner_refs=[OwnerReference(kind="NodePool", name="default")]))
        nc.metadata.finalizers.clear()
        store.create(nc)
        NodeClaimHydration(store).reconcile(nc)
        assert nc.metadata.labels[api_labels.NODEPOOL_LABEL_KEY] == "default"
        assert api_labels.TERMINATION_FINALIZER in nc.metadata.finalizers

    def test_hydration_idempotent(self):
        from karpenter_tpu.api.objects import ObjectMeta, OwnerReference
        from karpenter_tpu.controllers.hydration import NodeClaimHydration
        from karpenter_tpu.kube.store import Store
        store = Store(FakeClock())
        nc = NodeClaim(metadata=ObjectMeta(
            name="old-nc", namespace="",
            owner_refs=[OwnerReference(kind="NodePool", name="default")]))
        store.create(nc)
        h = NodeClaimHydration(store)
        h.reconcile(nc)
        rv = nc.metadata.resource_version
        h.reconcile(nc)  # second pass: nothing to backfill, no write
        assert nc.metadata.resource_version == rv
