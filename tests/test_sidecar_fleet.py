"""Replicated sidecar fleet (ISSUE 17): the client-side consistent-hash
tenant router, drain-to-peer migration behind the `migrated_to` NACK
rider, warm restore from the shared handoff store after a replica kill,
the stale-checkpoint digest catch-up path, the fleet scenario schema's
loud rejects, and a small fleet sim smoke proving the replica count is
invisible to scheduling truth."""

import os
from collections import Counter

import pytest

from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.sidecar import server as srv
from karpenter_tpu.sidecar.client import (ConsistentHashRouter,
                                          RemoteScheduler, RetryPolicy,
                                          SolverSession)
from karpenter_tpu.sim import (FleetSimulator, ScenarioError, load_scenario,
                               parse_scenario)

from factories import make_nodepool, make_pods

pytestmark = pytest.mark.fleet

SCENARIOS_DIR = os.path.join(os.path.dirname(__file__), "..",
                             "karpenter_tpu", "sim", "scenarios")


class TestConsistentHashRouter:
    ADDRS = ("127.0.0.1:7001", "127.0.0.1:7002", "127.0.0.1:7003")

    def test_empty_fleet_rejected(self):
        with pytest.raises(ValueError, match="at least one replica"):
            ConsistentHashRouter([])

    def test_routing_is_deterministic_and_coordination_free(self):
        """Two independent routers over the same fleet agree on every
        tenant's home — no control plane, no shared state."""
        a = ConsistentHashRouter(self.ADDRS)
        b = ConsistentHashRouter(list(self.ADDRS))
        for i in range(64):
            assert a.route(f"tenant-{i}") == b.route(f"tenant-{i}")

    def test_tenants_spread_across_the_fleet(self):
        counts = Counter(ConsistentHashRouter(self.ADDRS).route(f"t{i}")
                         for i in range(300))
        assert set(counts) == set(self.ADDRS)
        assert min(counts.values()) >= 300 * 0.15  # no starved replica

    def test_growing_the_fleet_moves_a_bounded_slice(self):
        """Consistent hashing's point: adding a replica re-homes ~1/N of
        tenants, never a wholesale reshuffle."""
        small = ConsistentHashRouter(self.ADDRS)
        grown = ConsistentHashRouter(self.ADDRS + ("127.0.0.1:7004",))
        moved = sum(small.route(f"t{i}") != grown.route(f"t{i}")
                    for i in range(400))
        assert 0 < moved <= 400 * 0.45

    def test_down_replica_walks_to_the_same_successor_everywhere(self):
        a = ConsistentHashRouter(self.ADDRS)
        b = ConsistentHashRouter(self.ADDRS)
        home = a.route("acme")
        a.mark_down(home)
        b.mark_down(home)
        assert a.route("acme") == b.route("acme") != home
        assert a.successor("acme", exclude=(home,)) == a.route("acme")

    def test_mark_down_is_a_cooldown_not_a_tombstone(self):
        clock = [0.0]
        r = ConsistentHashRouter(self.ADDRS, cooldown=5.0,
                                 clock=lambda: clock[0])
        home = r.route("acme")
        r.mark_down(home)
        assert r.route("acme") != home
        clock[0] = 5.1  # the restarted process rejoins, signal-free
        assert r.route("acme") == home

    def test_mark_up_restores_immediately(self):
        r = ConsistentHashRouter(self.ADDRS)
        home = r.route("acme")
        r.mark_down(home)
        r.mark_up(home)
        assert r.route("acme") == home

    def test_whole_fleet_down_hands_back_the_ring_owner(self):
        r = ConsistentHashRouter(self.ADDRS)
        for a in self.ADDRS:
            r.mark_down(a)
        assert r.route("acme") in self.ADDRS


# -- live fleets: migration, failover, catch-up -------------------------------


def _boot(n):
    """N isolated replicas sharing one handoff store, peers wired."""
    handoff = srv.HandoffStore()
    entries = []
    for i in range(n):
        rep = srv.Replica(name=f"fleet-test-{i}", handoff=handoff)
        server, port = srv.serve(port=0, replica=rep)
        entries.append([server, port, rep])
    addrs = [f"127.0.0.1:{p}" for _, p, _ in entries]
    for i, entry in enumerate(entries):
        entry[2].peers = tuple(a for j, a in enumerate(addrs) if j != i)
    return entries, addrs, handoff


def _stop(entries):
    for server, _, _ in entries:
        server.stop(grace=None)


def _fleet_session(addrs, tenant):
    policy = RetryPolicy(deadline=10.0, max_attempts=6, backoff_base=0.01,
                         backoff_cap=0.05, retry_budget=32.0, refund=1.0)
    session = SolverSession(addrs[0], tenant=tenant, retry=policy)
    session.enable_fleet(addrs)
    rs = RemoteScheduler(addrs[0], [make_nodepool()],
                         {"default": construct_instance_types()[:32]},
                         session=session)
    return rs, session


def _entry_for(entries, address):
    return next(e for e in entries if f"127.0.0.1:{e[1]}" == address)


class TestFleetMigration:
    def test_drain_names_the_peer_and_the_tenant_follows_warm(self):
        """server.drain() NACKs with a `migrated_to` rider; the client
        follows it to the named peer, which rebuilds the session from the
        drained replica's checkpoint — no cold bootstrap anywhere."""
        entries, addrs, handoff = _boot(2)
        try:
            rs, session = _fleet_session(addrs, "drain-tenant")
            pods = make_pods(6, cpu="500m")
            rs.solve(pods)
            home = session.address
            _entry_for(entries, home)[0].drain(grace=2.0)
            rs.solve(pods[1:] + make_pods(1, cpu="250m"))
            assert session.address != home
            assert session.failovers == 1
            assert session.resyncs == 0, "the migration cost a cold resync"
            assert handoff.restores >= 1
            session.close()
        finally:
            _stop(entries)

    def test_killed_replica_resumes_warm_on_the_ring_successor(self):
        """A hard kill (no drain, no rider): repeated UNAVAILABLE marks
        the replica down, the ring successor restores the session from
        its last post-solve checkpoint, and the tenant never resyncs."""
        entries, addrs, handoff = _boot(3)
        try:
            rs, session = _fleet_session(addrs, "kill-tenant")
            pods = make_pods(8, cpu="500m")
            rs.solve(pods)
            rs.solve(pods[:6])
            home = session.address
            _entry_for(entries, home)[0].stop(grace=None)
            rs.solve(pods[:6] + make_pods(2, cpu="750m"))
            assert session.address != home
            assert session.failovers >= 1
            assert session.resyncs == 0, "the kill cost a cold resync"
            assert handoff.restores >= 1
            session.close()
        finally:
            _stop(entries)

    def test_stale_checkpoint_catches_up_with_a_bounded_delta(self):
        """The successor restored an OLDER acked state (checkpoint lag):
        the digest handshake rejects, the server names its digest in the
        rider, and the client rolls its mirrors back and ships the
        bounded catch-up delta — counted as a catchup, NOT a resync."""
        entries, addrs, handoff = _boot(2)
        try:
            rs, session = _fleet_session(addrs, "stale-tenant")
            pods = make_pods(6, cpu="500m")
            rs.solve(pods)
            sid = session._session_id
            stale = handoff.get(sid)
            assert stale is not None  # post-solve checkpoint write
            rs.solve(pods[:4])
            rs.solve(pods[:4] + make_pods(2, cpu="250m"))
            handoff.put(sid, stale)  # rewind the store to solve-1 state
            home = session.address
            _entry_for(entries, home)[0].stop(grace=None)
            rs.solve(pods[:4] + make_pods(3, cpu="300m"))
            assert session.catchups == 1, \
                "the stale restore did not take the bounded catch-up path"
            assert session.resyncs == 0, \
                "the stale restore fell back to a full resync"
            session.close()
        finally:
            _stop(entries)

    def test_draining_replica_without_peers_still_nacks_retryably(self):
        """A single-replica 'fleet' drain has nowhere to point the rider;
        the retry lands back on the SAME (restarted) address. Here the
        server never restarts, so the solve must fail loudly after the
        budget — not hang, not corrupt."""
        import grpc
        entries, addrs, _ = _boot(1)
        try:
            rs, session = _fleet_session(addrs, "lonely")
            rs.solve(make_pods(3, cpu="500m"))
            entries[0][0].drain(grace=1.0)
            with pytest.raises(grpc.RpcError):
                rs.solve(make_pods(4, cpu="500m"))
            session.close()
        finally:
            _stop(entries)


# -- scenario schema: fleet keys reject loudly --------------------------------


def _doc(**over):
    doc = {
        "name": "t", "seed": 1, "duration": 600.0, "tick": 20,
        "events": [{"at": 5, "kind": "deploy", "name": "web", "replicas": 3,
                    "cpu": "500m", "memory": "256Mi"}],
    }
    doc.update(over)
    return doc


class TestFleetScenarioSchema:
    def test_replicas_require_the_sidecar_backend(self):
        with pytest.raises(ScenarioError,
                           match="requires 'backend: sidecar'"):
            parse_scenario(_doc(replicas=3))

    def test_rolling_restart_requires_a_fleet(self):
        doc = _doc(backend="sidecar")
        doc["events"].append({"at": 50, "kind": "rolling_restart"})
        with pytest.raises(ScenarioError, match="requires 'replicas: 1'"):
            parse_scenario(doc)

    def test_service_fleet_library_scenario_validates(self):
        sc = load_scenario(os.path.join(SCENARIOS_DIR, "service-fleet.yaml"))
        assert sc.backend == "sidecar" and sc.replicas == 3
        assert any(e.kind == "rolling_restart" for e in sc.events)
        assert any(e.kind == "wire_chaos" and e.params.get("kill_server")
                   for e in sc.events)


# -- fleet sim smoke: replica count is invisible to scheduling truth ----------


class TestFleetSimSmoke:
    DOC = {
        "name": "fleet-smoke", "seed": 23, "duration": 900.0, "tick": 20,
        "backend": "sidecar", "replicas": 2,
        "events": [
            {"at": 5, "kind": "deploy", "name": "web", "replicas": 4,
             "cpu": "500m", "memory": "256Mi"},
            {"at": 200, "kind": "wire_chaos", "kill_server": True,
             "replica": 1, "duration": 60},
            {"at": 400, "kind": "rolling_restart", "interval": 20,
             "drain_grace": 0.5},
            {"at": 700, "kind": "scale", "name": "web", "replicas": 7},
        ],
    }

    def _run(self, **over):
        import copy
        doc = copy.deepcopy(self.DOC)
        doc.update(over)
        sim = FleetSimulator(parse_scenario(doc))
        return sim.run()

    def test_fleet_run_restores_warm_and_never_resyncs(self):
        report = self._run()
        svc = report["service"]
        assert svc["replicas"] == 2
        assert svc["rolling_restarts"] == 2
        assert svc["checkpoint_restores"] >= 1
        assert svc["resyncs"] == 0, \
            "a kill or roll cost a cold bootstrap despite the checkpoints"
        assert report["final"]["pods_pending"] == 0

    def test_replica_count_is_digest_invisible(self):
        """The whole point of the fleet: same seed, 1 vs 2 replicas,
        byte-identical scheduling truth."""
        assert (self._run()["ledger_digest"]
                == self._run(replicas=1)["ledger_digest"])


class TestHandoffStoreBounds:
    """ISSUE 20 satellite: the shared checkpoint plane is BOUNDED. Before
    this, an orphaned session (owner died without a successor ever
    touching the checkpoint) pinned fleet-sized state forever; now the
    store LRU-evicts past max_entries and TTL-expires stale entries both
    lazily on read and from the idle-GC sweep — every eviction counted
    on karpenter_sidecar_handoff_evicted_total{reason}."""

    def _metric(self, reason):
        from karpenter_tpu.metrics.registry import SIDECAR_HANDOFF_EVICTED
        return SIDECAR_HANDOFF_EVICTED.value({"reason": reason})

    def test_cap_evicts_least_recently_used(self):
        store = srv.HandoffStore(max_entries=3, ttl_seconds=0)
        before = self._metric("cap")
        for i in range(3):
            store.put(f"s{i}", b"ck%d" % i)
        assert store.get("s0") == b"ck0"  # refresh: s1 is now the LRU
        store.put("s3", b"ck3")
        assert len(store) == 3
        assert store.get("s1") is None, "cap eviction must drop the LRU"
        assert store.get("s0") == b"ck0"
        assert store.evicted == 1
        assert self._metric("cap") == before + 1

    def test_ttl_expires_lazily_on_get(self):
        clock = {"t": 0.0}
        store = srv.HandoffStore(max_entries=8, ttl_seconds=60,
                                 now=lambda: clock["t"])
        before = self._metric("ttl")
        store.put("sess", b"ck")
        clock["t"] = 59.0
        assert store.get("sess") == b"ck"
        # the restore refreshed the TTL clock: still alive at t=118
        clock["t"] = 118.0
        assert store.get("sess") == b"ck"
        clock["t"] = 178.0
        assert store.get("sess") is None
        assert len(store) == 0
        assert self._metric("ttl") == before + 1

    def test_sweep_expires_orphans_in_bulk(self):
        clock = {"t": 0.0}
        store = srv.HandoffStore(max_entries=8, ttl_seconds=60,
                                 now=lambda: clock["t"])
        before = self._metric("ttl")
        for i in range(4):
            store.put(f"s{i}", b"ck")
        clock["t"] = 30.0
        store.put("fresh", b"ck")
        clock["t"] = 61.0
        assert store.sweep() == 4
        assert len(store) == 1 and store.get("fresh") == b"ck"
        assert store.evicted == 4
        assert self._metric("ttl") == before + 4

    def test_zero_ttl_disables_expiry(self):
        clock = {"t": 0.0}
        store = srv.HandoffStore(max_entries=8, ttl_seconds=0,
                                 now=lambda: clock["t"])
        store.put("sess", b"ck")
        clock["t"] = 1e9
        assert store.sweep() == 0
        assert store.get("sess") == b"ck"
