"""Scenario port of the pod (anti-)affinity half of
/root/reference/pkg/controllers/provisioning/scheduling/topology_test.go
(:1393-2449): cross-pod affinity, self-affinity bootstrap, zonal
anti-affinity incl. the Schrödinger batch-order case, inverse anti-affinity
from existing cluster pods, namespace filtering, and dependent-affinity
chains. Host oracle is the conformance target; kernel-eligible shapes are
additionally run through the tensor path."""

from collections import Counter

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (Affinity, LabelSelector,
                                       NodeSelectorRequirement, PodAffinity,
                                       PodAffinityTerm)
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import (StaticClusterView, affinity_term, make_nodepool,
                       make_pod, make_pods, make_scheduler, running_on)

ZONE = api_labels.LABEL_TOPOLOGY_ZONE
HOST = api_labels.LABEL_HOSTNAME
ARCH = api_labels.LABEL_ARCH


def its():
    return kwok.construct_instance_types()


def sel(**labels):
    return LabelSelector(match_labels=dict(labels))


def three_zone_pool():
    return make_nodepool(requirements=[NodeSelectorRequirement(
        ZONE, "In", ("test-zone-a", "test-zone-b", "test-zone-c"))])


def hsolve(pods, pools=None, catalog=None, view=None, state_nodes=()):
    pools = pools or [make_nodepool()]
    catalog = catalog if catalog is not None else its()
    s = make_scheduler(pools, catalog, pods, state_nodes=state_nodes,
                       cluster=view)
    return s.solve(pods)


def placement_of(results, pod):
    """(claim-or-node object, kind) hosting the pod, or (None, None)."""
    for nc in results.new_nodeclaims:
        if any(p.uid == pod.uid for p in nc.pods):
            return nc, "new"
    for en in results.existing_nodes:
        if any(p.uid == pod.uid for p in en.pods):
            return en, "existing"
    return None, None


class TestPodAffinity:
    def test_empty_affinity_schedules(self):
        pod = make_pod()
        pod.spec.affinity = Affinity(pod_affinity=PodAffinity(),
                                     pod_anti_affinity=PodAffinity())
        h = hsolve([pod])
        assert not h.pod_errors

    def test_affinity_hostname_colocates_with_target(self):
        """topology_test.go:1403-1436: followers land on the target's node."""
        target = make_pod(cpu="500m", labels={"app": "target"})
        followers = make_pods(5, cpu="100m", labels={"app": "client"},
                              pod_affinity=[PodAffinityTerm(
                                  topology_key=HOST,
                                  label_selector=sel(app="target"))])
        h = hsolve([target] + followers)
        assert not h.pod_errors
        tgt_claim, _ = placement_of(h, target)
        for f in followers:
            claim, _ = placement_of(h, f)
            assert claim is tgt_claim

    def test_affinity_arch_topology(self):
        """topology_test.go:1437-1479: affinity over the arch topology —
        followers share the target's architecture, not its node."""
        target = make_pod(labels={"app": "target"},
                          node_selector={ARCH: "arm64"})
        followers = make_pods(3, labels={"app": "client"},
                              pod_affinity=[PodAffinityTerm(
                                  topology_key=ARCH,
                                  label_selector=sel(app="target"))])
        h = hsolve([target] + followers)
        assert not h.pod_errors
        for f in followers:
            claim, _ = placement_of(h, f)
            assert claim.requirements.get(ARCH).values_list() == ["arm64"]

    def test_self_affinity_first_empty_domain_only(self):
        """topology_test.go:1504-1545: the hostname domain is fixed by the
        first placement; overflow beyond one node's capacity is
        unschedulable, never a second node."""
        small = [it for it in its() if it.capacity.get("cpu", 0) <= 2000]
        pods = make_pods(10, cpu="500m", labels={"security": "s2"},
                         pod_affinity=[PodAffinityTerm(
                             topology_key=HOST,
                             label_selector=sel(security="s2"))])
        h = hsolve(pods, catalog=small)
        assert len(h.new_nodeclaims) == 1
        assert len(h.pod_errors) > 0
        assert len(h.new_nodeclaims[0].pods) + len(h.pod_errors) == 10

    def test_self_affinity_zone_with_constraint(self):
        """topology_test.go:1614-1644: a zone selector on the pods narrows
        the self-affinity domain to that zone."""
        pods = make_pods(4, labels={"security": "s2"},
                         node_selector={ZONE: "test-zone-b"},
                         pod_affinity=[PodAffinityTerm(
                             topology_key=ZONE,
                             label_selector=sel(security="s2"))])
        h = hsolve(pods)
        assert not h.pod_errors
        for nc in h.new_nodeclaims:
            assert nc.requirements.get(ZONE).values_list() == ["test-zone-b"]

    def test_preferred_affinity_violated_when_impossible(self):
        """topology_test.go:1698-1730: preferred affinity to a pod that
        doesn't exist relaxes away."""
        pods = make_pods(2, labels={"app": "client"},
                         preferred_pod_affinity=[(10, PodAffinityTerm(
                             topology_key=HOST,
                             label_selector=sel(app="no-such")))])
        h = hsolve(pods)
        assert not h.pod_errors

    def test_preferred_anti_affinity_violated_when_needed(self):
        """topology_test.go:1731-1763."""
        pods = make_pods(3, cpu="100m", labels={"app": "demo"},
                         preferred_pod_anti_affinity=[(10, PodAffinityTerm(
                             topology_key=ZONE,
                             label_selector=sel(app="demo")))])
        pool = three_zone_pool()
        h = hsolve(pods + make_pods(2, cpu="100m", labels={"app": "demo"},
                                    preferred_pod_anti_affinity=[
                                        (10, PodAffinityTerm(
                                            topology_key=ZONE,
                                            label_selector=sel(app="demo")))]),
                   pools=[pool])
        # 5 pods, 3 zones: at least two must violate the preference
        assert not h.pod_errors

    def test_affinity_to_non_existent_pod_unschedulable(self):
        """topology_test.go:2177-2193 — also kernel-eligible (non-self
        zonal affinity with no matches has no bootstrap)."""
        def pods():
            return make_pods(2, labels={"app": "client"},
                             pod_affinity=[PodAffinityTerm(
                                 topology_key=ZONE,
                                 label_selector=sel(app="no-such"))])
        h = hsolve(pods())
        assert len(h.pod_errors) == 2
        it_map = {"default": its()}
        ts = TensorScheduler([make_nodepool()], it_map, force_tensor=True)
        t = ts.solve(pods())
        assert ts.fallback_reason == ""
        assert len(t.pod_errors) == 2

    def test_multiple_dependent_affinities(self):
        """topology_test.go:2256-2290: a -> b -> c -> d hostname chain all
        collapse onto one node."""
        a = make_pod(cpu="100m", labels={"app": "a"})
        b = make_pod(cpu="100m", labels={"app": "b"},
                     pod_affinity=[PodAffinityTerm(topology_key=HOST,
                                                   label_selector=sel(app="a"))])
        c = make_pod(cpu="100m", labels={"app": "c"},
                     pod_affinity=[PodAffinityTerm(topology_key=HOST,
                                                   label_selector=sel(app="b"))])
        d = make_pod(cpu="100m", labels={"app": "d"},
                     pod_affinity=[PodAffinityTerm(topology_key=HOST,
                                                   label_selector=sel(app="c"))])
        h = hsolve([a, b, c, d])
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 1

    def test_unsatisfiable_dependency_fails(self):
        """topology_test.go:2291-2306: b must join a's node but is pinned to
        a different zone."""
        a = make_pod(cpu="100m", labels={"app": "a"},
                     node_selector={ZONE: "test-zone-a"})
        b = make_pod(cpu="100m", labels={"app": "b"},
                     node_selector={ZONE: "test-zone-b"},
                     pod_affinity=[PodAffinityTerm(topology_key=HOST,
                                                   label_selector=sel(app="a"))])
        h = hsolve([a, b])
        assert len(h.pod_errors) == 1
        assert b.uid in h.pod_errors


class TestPodAntiAffinity:
    def test_separate_nodes_on_hostname(self):
        """topology_test.go:1764-1785, both batch orders."""
        for order in (0, 1):
            target = make_pod(cpu="500m", labels={"security": "s2"})
            avoider = make_pod(cpu="500m",
                               pod_anti_affinity=[PodAffinityTerm(
                                   topology_key=HOST,
                                   label_selector=sel(security="s2"))])
            batch = [avoider, target] if order == 0 else [target, avoider]
            h = hsolve(batch)
            assert not h.pod_errors
            c1, _ = placement_of(h, target)
            c2, _ = placement_of(h, avoider)
            assert c1 is not c2

    def test_anti_zone_all_zones_occupied(self):
        """topology_test.go:1786-1824: matching pods pinned to every zone
        the pool offers -> the avoider is unschedulable."""
        pool = three_zone_pool()
        zoned = [make_pod(cpu="2", labels={"security": "s2"},
                          node_selector={ZONE: z})
                 for z in ("test-zone-a", "test-zone-b", "test-zone-c")]
        avoider = make_pod(pod_anti_affinity=[PodAffinityTerm(
            topology_key=ZONE, label_selector=sel(security="s2"))])
        h = hsolve(zoned + [avoider], pools=[pool])
        assert set(h.pod_errors) == {avoider.uid}

    def test_anti_zone_target_zone_unknown(self):
        """topology_test.go:1825-1846: the matching pod schedules anywhere,
        so every zone is potentially poisoned within the batch."""
        pool = three_zone_pool()
        target = make_pod(cpu="2", labels={"security": "s2"})
        avoider = make_pod(pod_anti_affinity=[PodAffinityTerm(
            topology_key=ZONE, label_selector=sel(security="s2"))])
        h = hsolve([target, avoider], pools=[pool])
        assert set(h.pod_errors) == {avoider.uid}

    def test_anti_zone_schroedinger(self):
        """topology_test.go:1966-1996: in-batch, the avoider commits first
        (FFD order) and poisons every zone for the matching pod; once the
        avoider is COMMITTED to a zone (next batch, via the cluster), the
        matching pod schedules into another zone."""
        pool = three_zone_pool()
        avoider = make_pod(cpu="2", pod_anti_affinity=[PodAffinityTerm(
            topology_key=ZONE, label_selector=sel(security="s2"))])
        labeled = make_pod(cpu="100m", labels={"security": "s2"})
        h = hsolve([avoider, labeled], pools=[pool])
        assert set(h.pod_errors) == {labeled.uid}
        claim, _ = placement_of(h, avoider)
        # the claim stays UNcommitted across the pool's zones — the actual
        # zone is decided at node creation (that's the Schrödinger point)
        options = claim.requirements.get(ZONE).values_list()
        assert len(options) == 3
        committed = sorted(options)[0]  # node creation picks one

        # batch 2: the avoider is now a running pod on a real node
        view = StaticClusterView(
            running_on([avoider], "node-committed"),
            {"node-committed": {ZONE: committed,
                                HOST: "node-committed"}})
        labeled2 = make_pod(cpu="100m", labels={"security": "s2"})
        h2 = hsolve([labeled2], pools=[pool], view=view)
        assert not h2.pod_errors
        claim2, _ = placement_of(h2, labeled2)
        z2 = claim2.requirements.get(ZONE).values_list()
        assert committed not in z2

    def test_inverse_anti_affinity_with_existing_pods(self):
        """topology_test.go:1997-2046: existing pods with required
        anti-affinity in every pool zone block a matching newcomer."""
        pool = three_zone_pool()
        anti = [PodAffinityTerm(topology_key=ZONE,
                                label_selector=sel(security="s2"))]
        existing, labels_map = [], {}
        for i, z in enumerate(("test-zone-a", "test-zone-b", "test-zone-c")):
            p = make_pod(cpu="2", pod_anti_affinity=list(anti))
            running_on([p], f"anti-node-{i}")
            existing.append(p)
            labels_map[f"anti-node-{i}"] = {ZONE: z, HOST: f"anti-node-{i}"}
        view = StaticClusterView(existing, labels_map)
        newcomer = make_pod(labels={"security": "s2"})
        h = hsolve([newcomer], pools=[pool], view=view)
        assert set(h.pod_errors) == {newcomer.uid}

    def test_preferred_inverse_anti_affinity_is_ignored(self):
        """topology_test.go:2047-2096: only REQUIRED anti-affinity terms of
        existing pods poison domains; preferred terms don't."""
        pool = three_zone_pool()
        existing, labels_map = [], {}
        for i, z in enumerate(("test-zone-a", "test-zone-b", "test-zone-c")):
            p = make_pod(cpu="2", preferred_pod_anti_affinity=[
                (10, PodAffinityTerm(topology_key=ZONE,
                                     label_selector=sel(security="s2")))])
            running_on([p], f"pref-node-{i}")
            existing.append(p)
            labels_map[f"pref-node-{i}"] = {ZONE: z, HOST: f"pref-node-{i}"}
        view = StaticClusterView(existing, labels_map)
        newcomer = make_pod(labels={"security": "s2"})
        h = hsolve([newcomer], pools=[pool], view=view)
        assert not h.pod_errors

    def test_anti_affinity_via_zone_topology_batch(self):
        """topology_test.go:2132-2176: N mutually-anti pods, one schedules
        per batch (late committal) — and the tensor path agrees."""
        def pods():
            return make_pods(3, labels={"app": "demo"},
                             pod_anti_affinity=[affinity_term(ZONE)])
        h = hsolve(pods())
        assert len(h.pod_errors) == 2
        it_map = {"default": its()}
        ts = TensorScheduler([make_nodepool()], it_map, force_tensor=True)
        t = ts.solve(pods())
        assert len(t.pod_errors) == 2


class TestAffinityNamespaces:
    """topology_test.go:2307-2449."""

    def _target_elsewhere(self):
        target = make_pod(labels={"app": "target"}, namespace="other")
        running_on([target], "other-node")
        return StaticClusterView([target], {
            "other-node": {ZONE: "test-zone-a", HOST: "other-node"}})

    def test_no_namespaces_no_matches(self):
        """Matching pods in another namespace don't count without an
        explicit namespace list -> affinity unsatisfiable."""
        view = self._target_elsewhere()
        follower = make_pod(labels={"app": "client"},
                            pod_affinity=[PodAffinityTerm(
                                topology_key=ZONE,
                                label_selector=sel(app="target"))])
        h = hsolve([follower], view=view)
        assert set(h.pod_errors) == {follower.uid}

    def test_namespace_list_matches(self):
        view = self._target_elsewhere()
        follower = make_pod(labels={"app": "client"},
                            pod_affinity=[PodAffinityTerm(
                                topology_key=ZONE,
                                label_selector=sel(app="target"),
                                namespaces=("other",))])
        h = hsolve([follower], view=view)
        assert not h.pod_errors
        claim, _ = placement_of(h, follower)
        assert claim.requirements.get(ZONE).values_list() == ["test-zone-a"]
