"""Scenario port of /root/reference/pkg/controllers/provisioning/scheduling/
topology_test.go (2,502 LoC of Ginkgo tables): zonal/hostname/capacity-type/
arch spreads, minDomains, spread-option limiting, pod (anti-)affinity,
inverse anti-affinity, namespace filtering, taints. The host oracle is the
conformance target; scenarios the tensor kernel claims are additionally
asserted tensor-vs-host (tensor_solve) — the rest run host-only."""

from collections import Counter

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (LabelSelector, NodeSelectorRequirement,
                                       PodAffinityTerm, Taint, Toleration,
                                       TopologySpreadConstraint)
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import (StaticClusterView, affinity_term, make_nodepool,
                       make_pod, make_pods, make_scheduler, make_state_node,
                       running_on)

ZONE = api_labels.LABEL_TOPOLOGY_ZONE
HOST = api_labels.LABEL_HOSTNAME
CT = api_labels.CAPACITY_TYPE_LABEL_KEY
ARCH = api_labels.LABEL_ARCH
ZONES = ("test-zone-a", "test-zone-b", "test-zone-c", "test-zone-d")


def its():
    return kwok.construct_instance_types()


def tsc(key=ZONE, max_skew=1, value="demo", min_domains=None,
        anyway=False, expressions=None):
    sel = (LabelSelector(match_expressions=tuple(expressions))
           if expressions is not None
           else LabelSelector(match_labels={"app": value}))
    return TopologySpreadConstraint(
        topology_key=key, max_skew=max_skew,
        when_unsatisfiable=("ScheduleAnyway" if anyway else "DoNotSchedule"),
        label_selector=sel, min_domains=min_domains)


def zone_pool(*zones, name="default"):
    return make_nodepool(name=name, requirements=[
        NodeSelectorRequirement(ZONE, "In", tuple(zones))])


def hsolve(pods, pools=None, catalog=None, view=None, state_nodes=()):
    pools = pools or [make_nodepool()]
    catalog = catalog if catalog is not None else its()
    s = make_scheduler(pools, catalog, pods, state_nodes=state_nodes,
                       cluster=view)
    return s.solve(pods)


def tsolve(pods, pools=None, catalog=None, view=None, state_nodes=()):
    pools = pools or [make_nodepool()]
    catalog = catalog if catalog is not None else its()
    it_map = {p.name: list(catalog) for p in pools}
    ts = TensorScheduler(pools, it_map, state_nodes=state_nodes,
                         cluster=view, force_tensor=True)
    r = ts.solve(pods)
    assert ts.fallback_reason == "", ts.fallback_reason
    return r


def domain_fill(results, key) -> Counter:
    """pods per domain over new claims whose `key` narrowed to one value."""
    out = Counter()
    for nc in results.new_nodeclaims:
        vals = nc.requirements.get(key).values_list()
        if len(vals) == 1:
            out[vals[0]] += len(nc.pods)
    for en in results.existing_nodes:
        if en.pods:
            vals = en.requirements.get(key).values_list()
            if len(vals) == 1:
                out[vals[0]] += len(en.pods)
    return out


def skew(results, key=ZONE, extra=()):
    """Order-insensitive per-domain counts, ExpectSkew/ConsistOf style."""
    c = domain_fill(results, key)
    for d in extra:
        c[d] += 1
    return sorted(c.values())


class TestZonalSpread:
    """topology_test.go:93-530."""

    def test_balance_across_zones_match_labels(self):
        def pods():
            return make_pods(5, labels={"app": "demo"}, spread=[tsc()])
        h = hsolve(pods())
        assert not h.pod_errors
        assert skew(h) == [1, 1, 1, 2]
        t = tsolve(pods())
        assert skew(t) == [1, 1, 1, 2]

    def test_balance_across_zones_match_expressions(self):
        expr = [NodeSelectorRequirement("app", "In", ("demo",))]
        def pods():
            return make_pods(5, labels={"app": "demo"},
                             spread=[tsc(expressions=expr)])
        h = hsolve(pods())
        assert not h.pod_errors
        assert skew(h) == [1, 1, 1, 2]
        t = tsolve(pods())
        assert skew(t) == [1, 1, 1, 2]

    def test_respects_nodepool_zonal_constraints(self):
        pool = zone_pool("test-zone-a", "test-zone-b")
        def pods():
            return make_pods(6, labels={"app": "demo"}, spread=[tsc()])
        h = hsolve(pods(), pools=[pool])
        assert not h.pod_errors
        assert skew(h) == [3, 3]
        assert set(domain_fill(h, ZONE)) == {"test-zone-a", "test-zone-b"}
        t = tsolve(pods(), pools=[pool])
        assert skew(t) == [3, 3]

    def test_subset_with_pool_labels(self):
        # the pool pins the zone via a template label: one domain only
        pool = make_nodepool(labels={ZONE: "test-zone-c"})
        h = hsolve(make_pods(4, labels={"app": "demo"}, spread=[tsc()]),
                   pools=[pool])
        assert not h.pod_errors
        assert dict(domain_fill(h, ZONE)) == {"test-zone-c": 4}

    def test_existing_pod_counts_toward_skew(self):
        """topology_test.go:218-251: one matching pod already in zone-c, the
        pool restricted to a/b -> max 2 per zone before skew violation."""
        existing = running_on(make_pods(1, labels={"app": "demo"}),
                              "node-c")
        view = StaticClusterView(existing, {
            "node-c": {ZONE: "test-zone-c", HOST: "node-c"}})
        pool = zone_pool("test-zone-a", "test-zone-b")
        def pods():
            return make_pods(6, cpu="1100m", labels={"app": "demo"},
                             spread=[tsc()])
        h = hsolve(pods(), pools=[pool], view=view)
        assert len(h.pod_errors) == 2
        assert skew(h, extra=["test-zone-c"]) == [1, 2, 2]
        t = tsolve(pods(), pools=[pool], view=view)
        assert len(t.pod_errors) == 2
        assert skew(t, extra=["test-zone-c"]) == [1, 2, 2]

    def test_non_minimum_domain_if_all_thats_available(self):
        """topology_test.go:252-293, adapted: existing matching pods in
        zones a and b (1 each); the pool only offers zone-c; maxSkew=5
        allows up to 6 in zone-c (6-1 <= 5), the rest fail."""
        ex = (running_on(make_pods(1, labels={"app": "demo"}), "node-a")
              + running_on(make_pods(1, labels={"app": "demo"}), "node-b"))
        view = StaticClusterView(ex, {
            "node-a": {ZONE: "test-zone-a", HOST: "node-a"},
            "node-b": {ZONE: "test-zone-b", HOST: "node-b"}})
        pool = zone_pool("test-zone-c")
        def pods():
            return make_pods(10, labels={"app": "demo"},
                             spread=[tsc(max_skew=5)])
        h = hsolve(pods(), pools=[pool], view=view)
        assert len(h.pod_errors) == 4
        assert dict(domain_fill(h, ZONE)) == {"test-zone-c": 6}
        t = tsolve(pods(), pools=[pool], view=view)
        assert len(t.pod_errors) == 4
        assert dict(domain_fill(t, ZONE)) == {"test-zone-c": 6}

    def test_recovers_preexisting_skew(self):
        """topology_test.go:294-332: cluster already skewed (3,0,0,0);
        3 new pods only fill the minimum domains."""
        ex = running_on(make_pods(3, labels={"app": "demo"}), "node-a")
        view = StaticClusterView(ex, {
            "node-a": {ZONE: "test-zone-a", HOST: "node-a"}})
        def pods():
            return make_pods(3, labels={"app": "demo"}, spread=[tsc()])
        h = hsolve(pods(), view=view)
        assert not h.pod_errors
        fills = domain_fill(h, ZONE)
        assert fills["test-zone-a"] == 0 and sum(fills.values()) == 3
        t = tsolve(pods(), view=view)
        assert domain_fill(t, ZONE) == fills

    def test_unreachable_empty_zone_pins_global_min(self):
        """A zero-count zone offered only by a pool the pod can't use (an
        intolerable taint) still floors the reference's global min at 0
        (topologygroup.go:229-250): with two matching cluster pods in
        zone-a, maxSkew=1 blocks further zone-a placement on both paths."""
        pool_a = zone_pool("test-zone-a", name="pool-a")
        pool_b = make_nodepool(name="pool-b", requirements=[
            NodeSelectorRequirement(ZONE, "In", ("test-zone-b",))],
            taints=[Taint(key="dedicated", value="x")])
        ex = running_on(make_pods(2, labels={"app": "demo"}), "node-a")
        view = StaticClusterView(ex, {
            "node-a": {ZONE: "test-zone-a", HOST: "node-a"}})
        def pods():
            return make_pods(1, labels={"app": "demo"}, spread=[tsc()])
        h = hsolve(pods(), pools=[pool_a, pool_b], view=view)
        assert len(h.pod_errors) == 1
        t = tsolve(pods(), pools=[pool_a, pool_b], view=view)
        assert len(t.pod_errors) == 1

    def test_counts_only_running_scheduled_matching_pods(self):
        """topology_test.go:398-430: terminal, terminating, unscheduled, and
        non-matching pods don't count toward domain occupancy."""
        ignored = []
        terminal = running_on(make_pods(1, labels={"app": "demo"}), "node-a")
        terminal[0].status.phase = "Succeeded"
        ignored += terminal
        unsched = make_pods(1, labels={"app": "demo"})  # no node_name
        ignored += unsched
        deleting = running_on(make_pods(1, labels={"app": "demo"}), "node-a")
        deleting[0].metadata.deletion_timestamp = 1.0
        ignored += deleting
        other = running_on(make_pods(1, labels={"app": "not-demo"}), "node-a")
        ignored += other
        view = StaticClusterView(ignored, {
            "node-a": {ZONE: "test-zone-a", HOST: "node-a"}})
        h = hsolve(make_pods(4, labels={"app": "demo"}, spread=[tsc()]),
                   view=view)
        assert not h.pod_errors
        assert skew(h) == [1, 1, 1, 1]  # zone-a got no head start

    def test_interdependent_selector_matches_nothing(self):
        """topology_test.go:443-467: a hostname spread whose selector matches
        no pod (not even its owner) never accrues counts -> all pods may
        share one node."""
        def pods():
            return make_pods(5, cpu="100m",
                             spread=[tsc(key=HOST, value="no-such-app")])
        h = hsolve(pods())
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 1
        t = tsolve(pods())
        assert not t.pod_errors
        assert len(t.new_nodeclaims) == 1


class TestMinDomains:
    """topology_test.go:468-530."""

    def test_min_domains_blocks_when_fewer_domains(self):
        pool = zone_pool("test-zone-a", "test-zone-b")
        def pods():
            return make_pods(3, labels={"app": "demo"},
                             spread=[tsc(min_domains=3)])
        h = hsolve(pods(), pools=[pool])
        assert len(h.pod_errors) == 1
        assert skew(h) == [1, 1]
        t = tsolve(pods(), pools=[pool])
        assert len(t.pod_errors) == 1
        assert skew(t) == [1, 1]

    def test_min_domains_equal_allows_scheduling(self):
        pool = zone_pool("test-zone-a", "test-zone-b", "test-zone-c")
        def pods():
            return make_pods(11, labels={"app": "demo"},
                             spread=[tsc(min_domains=3)])
        h = hsolve(pods(), pools=[pool])
        assert not h.pod_errors
        assert skew(h) == [3, 4, 4]
        t = tsolve(pods(), pools=[pool])
        assert skew(t) == [3, 4, 4]

    def test_min_domains_below_count_allows_scheduling(self):
        pool = zone_pool("test-zone-a", "test-zone-b", "test-zone-c")
        def pods():
            return make_pods(11, labels={"app": "demo"},
                             spread=[tsc(min_domains=2)])
        h = hsolve(pods(), pools=[pool])
        assert not h.pod_errors
        assert skew(h) == [3, 4, 4]
        t = tsolve(pods(), pools=[pool])
        assert skew(t) == [3, 4, 4]


class TestHostnameSpread:
    """topology_test.go:531-638."""

    def test_balance_across_nodes(self):
        def pods():
            return make_pods(4, labels={"app": "demo"},
                             spread=[tsc(key=HOST)])
        h = hsolve(pods())
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 4
        t = tsolve(pods())
        assert len(t.new_nodeclaims) == 4

    def test_same_hostname_up_to_maxskew(self):
        def pods():
            return make_pods(4, cpu="100m", labels={"app": "demo"},
                             spread=[tsc(key=HOST, max_skew=4)])
        h = hsolve(pods())
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 1
        t = tsolve(pods())
        assert len(t.new_nodeclaims) == 1

    def test_multiple_deployments_spread_independently(self):
        """topology_test.go:557-592: two deployments, each hostname-spread
        on its own selector; counts never couple."""
        def pods():
            return (make_pods(3, cpu="100m", labels={"app": "a"},
                              spread=[tsc(key=HOST, value="a")])
                    + make_pods(3, cpu="100m", labels={"app": "b"},
                                spread=[tsc(key=HOST, value="b")]))
        h = hsolve(pods())
        assert not h.pod_errors
        # every node hosts at most 1 of each app
        for nc in h.new_nodeclaims:
            per = Counter(p.labels.get("app") for p in nc.pods)
            assert all(v <= 1 for v in per.values())
        t = tsolve(pods())
        assert not t.pod_errors
        for nc in t.new_nodeclaims:
            per = Counter(p.labels.get("app") for p in nc.pods)
            assert all(v <= 1 for v in per.values())


class TestCapacityTypeAndArchSpread:
    """topology_test.go:639-926 — non-zone/hostname topology keys stay on
    the host oracle (the kernel demotes them)."""

    def test_balance_across_capacity_types(self):
        h = hsolve(make_pods(2, labels={"app": "demo"},
                             spread=[tsc(key=CT)]))
        assert not h.pod_errors
        assert skew(h, key=CT) == [1, 1]

    def test_respects_nodepool_capacity_type_constraint(self):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(CT, "In", ("spot",))])
        h = hsolve(make_pods(2, labels={"app": "demo"},
                             spread=[tsc(key=CT)]), pools=[pool])
        assert not h.pod_errors
        assert dict(domain_fill(h, CT)) == {"spot": 2}

    def test_max_skew_binds_on_capacity_type(self):
        """topology_test.go:667-701: 3 pods forced to spot first, then
        spread pods must backfill on-demand before spot again."""
        ex = running_on(make_pods(3, labels={"app": "demo"}), "node-s")
        view = StaticClusterView(ex, {
            "node-s": {CT: "spot", ZONE: "test-zone-a", HOST: "node-s"}})
        h = hsolve(make_pods(3, labels={"app": "demo"},
                             spread=[tsc(key=CT)]), view=view)
        assert not h.pod_errors
        fills = domain_fill(h, CT)
        assert fills["on-demand"] == 3 and fills["spot"] == 0

    def test_balance_across_arch(self):
        h = hsolve(make_pods(2, labels={"app": "demo"},
                             spread=[tsc(key=ARCH)]))
        assert not h.pod_errors
        assert skew(h, key=ARCH) == [1, 1]

    def test_zonal_and_hostname_constraints_together(self):
        """topology_test.go:927-966."""
        def pods():
            return make_pods(8, cpu="100m", labels={"app": "demo"},
                             spread=[tsc(), tsc(key=HOST, max_skew=1)])
        h = hsolve(pods())
        assert not h.pod_errors
        assert skew(h) == [2, 2, 2, 2]
        assert all(len(nc.pods) <= 1 for nc in h.new_nodeclaims)
        t = tsolve(pods())
        assert skew(t) == [2, 2, 2, 2]
        assert all(len(nc.pods) <= 1 for nc in t.new_nodeclaims)

    def test_zonal_and_capacity_type_constraints_together(self):
        h = hsolve(make_pods(8, labels={"app": "demo"},
                             spread=[tsc(), tsc(key=CT)]))
        assert not h.pod_errors
        assert skew(h) == [2, 2, 2, 2]
        assert skew(h, key=CT) == [4, 4]

    def test_all_three_constraints_together(self):
        """topology_test.go:1169-1206."""
        h = hsolve(make_pods(8, cpu="100m", labels={"app": "demo"},
                             spread=[tsc(), tsc(key=CT),
                                     tsc(key=HOST, max_skew=3)]))
        assert not h.pod_errors
        assert skew(h) == [2, 2, 2, 2]
        assert skew(h, key=CT) == [4, 4]
        assert all(len(nc.pods) <= 3 for nc in h.new_nodeclaims)


class TestSpreadOptionLimiting:
    """topology_test.go:1207-1392."""

    def test_limited_by_node_selector(self):
        def pods():
            return make_pods(4, labels={"app": "demo"},
                             node_selector={ZONE: "test-zone-a"},
                             spread=[tsc()])
        h = hsolve(pods())
        assert not h.pod_errors
        assert dict(domain_fill(h, ZONE)) == {"test-zone-a": 4}
        t = tsolve(pods())
        assert dict(domain_fill(t, ZONE)) == {"test-zone-a": 4}

    def test_limited_by_required_node_affinity(self):
        req = [[NodeSelectorRequirement(ZONE, "In",
                                        ("test-zone-a", "test-zone-b"))]]
        def pods():
            return make_pods(6, labels={"app": "demo"},
                             required_affinity=req, spread=[tsc()])
        h = hsolve(pods())
        assert not h.pod_errors
        assert skew(h) == [3, 3]
        assert set(domain_fill(h, ZONE)) == {"test-zone-a", "test-zone-b"}
        t = tsolve(pods())
        assert skew(t) == [3, 3]

    def test_not_limited_by_preferred_node_affinity(self):
        """topology_test.go:1299-1323: preferences do NOT restrict the
        domain universe the spread may use."""
        pref = [(1, [NodeSelectorRequirement(ZONE, "In", ("test-zone-a",))])]
        h = hsolve(make_pods(8, labels={"app": "demo"},
                             preferred_affinity=pref, spread=[tsc()]))
        assert not h.pod_errors
        assert skew(h) == [2, 2, 2, 2]


class TestNodePoolTaints:
    """suite_test.go:2450-2500."""

    def test_tainted_pool_rejects_intolerant_pods(self):
        pool = make_nodepool(taints=[Taint(key="dedicated", value="gpu")])
        h = hsolve(make_pods(2), pools=[pool])
        assert len(h.pod_errors) == 2

    def test_tolerating_pods_schedule_on_tainted_pool(self):
        pool = make_nodepool(taints=[Taint(key="dedicated", value="gpu")])
        tol = [Toleration(key="dedicated", operator="Exists")]
        h = hsolve(make_pods(2, tolerations=tol), pools=[pool])
        assert not h.pod_errors

    def test_startup_taints_do_not_block_scheduling(self):
        pool = make_nodepool(startup_taints=[Taint(key="init", value="x")])
        h = hsolve(make_pods(2), pools=[pool])
        assert not h.pod_errors


class TestNodePoolRequirementSpread:
    """topology_test.go:967-1042: a custom topology key whose domains are
    DEFINED by two pools' requirements — spread must balance across pools."""

    def test_balance_across_nodepool_requirement_domains(self):
        pool_a = make_nodepool(name="pool-a", requirements=[
            NodeSelectorRequirement("example.com/shard", "In", ("s1",))])
        pool_b = make_nodepool(name="pool-b", requirements=[
            NodeSelectorRequirement("example.com/shard", "In", ("s2",))])
        pods = make_pods(8, cpu="500m", labels={"app": "demo"},
                         spread=[tsc(key="example.com/shard")])
        r = hsolve(pods, pools=[pool_a, pool_b])
        assert not r.pod_errors
        counts = domain_fill(r, "example.com/shard")
        assert set(counts) == {"s1", "s2"}
        assert abs(counts["s1"] - counts["s2"]) <= 1

    def test_schedule_anyway_violates_capacity_type_skew(self):
        """topology_test.go:702-732: a REAL violation — one matching pod
        already runs on spot, the pool now only offers on-demand, so every
        new pod widens the skew; ScheduleAnyway lands them regardless."""
        existing = running_on(make_pods(1, labels={"app": "demo"}),
                              "node-spot")
        view = StaticClusterView(existing, {
            "node-spot": {CT: api_labels.CAPACITY_TYPE_SPOT,
                          HOST: "node-spot"}})
        pool = make_nodepool(name="default", requirements=[
            NodeSelectorRequirement(CT, "In",
                                    (api_labels.CAPACITY_TYPE_ON_DEMAND,))])
        def pods():
            return make_pods(5, cpu="500m", labels={"app": "demo"},
                             spread=[tsc(key=CT, anyway=True)])
        r = hsolve(pods(), pools=[pool])  # without the view: trivially fine
        assert not r.pod_errors
        r = hsolve(pods(), pools=[pool], view=view)
        # skew ends at (spot=1, on-demand=5): violated, but ScheduleAnyway
        assert not r.pod_errors
        assert domain_fill(r, CT)[api_labels.CAPACITY_TYPE_ON_DEMAND] == 5

    def test_do_not_schedule_ignores_unreachable_capacity_type_domain(self):
        """A spot-only pool makes the on-demand domain unreachable: skew is
        computed within the reachable domain alone, so nothing blocks."""
        pool = make_nodepool(name="default", requirements=[
            NodeSelectorRequirement(CT, "In",
                                    (api_labels.CAPACITY_TYPE_SPOT,))])
        pods = make_pods(6, cpu="500m", labels={"app": "demo"},
                         spread=[tsc(key=CT, max_skew=1)])
        r = hsolve(pods, pools=[pool])
        assert not r.pod_errors
