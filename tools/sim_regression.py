#!/usr/bin/env python
"""Sim digest regression gate: byte-exact perf-behavior pinning for CI.

Replays a clipped library scenario (mixed-day, first CLIP_SECONDS of
simulated time) through the FULL operator loop and compares two things
against a pinned golden (tests/goldens/sim-regression.json):

- the deterministic event-ledger DIGEST — same seed + scenario + code
  must produce a byte-identical ledger (the PR-9 determinism contract),
  so ANY behavior change in the solver, the disruption engine, the wire,
  or the chaos actuators flips this hash. This is the perf-behavior pin
  the ROADMAP asked for where wall-clock asserts flake: a 2-core CI box
  can't slow a digest down.
- the SLO-report SHAPE — the dotted key paths and value types of the
  report dict, so a section silently vanishing (or a type drifting from
  number to string) fails loudly even though values are run-volatile.

On mismatch the gate exits 1 and prints the one command that refreshes
the pin — a deliberate behavior change regenerates, an accidental one
gets reviewed:

    python tools/sim_regression.py --update

Run the gate itself with no arguments (exit 0 = green). Tier-1 wraps this
module in tests/test_sim_regression.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from anywhere, venv or not
    sys.path.insert(0, REPO)
# state-chaos pins a device-loss window: its digest-parity contract needs
# >= 2 surviving solver devices (one survivor short of that, the ladder
# exhausts into the host oracle and the solve ledger's `fallback` field
# diverges from the fault-free run). Match the tests/conftest.py device
# count BEFORE jax is first imported; a no-op when conftest already did.
_xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla:
    os.environ["XLA_FLAGS"] = \
        (_xla + " --xla_force_host_platform_device_count=8").strip()
GOLDEN_PATH = os.path.join(REPO, "tests", "goldens", "sim-regression.json")
SCENARIO = "mixed-day.yaml"
CLIP_SECONDS = 7200.0
# every (scenario, clip) pair the gate pins; the first entry is the
# historical mixed-day pin, disruption-wave (ISSUE 14) clips past its
# drift wave so the streaming disruption engine's decisions are part of
# the byte-exact contract, service-fleet (ISSUE 17) pins the 3-replica
# sidecar fleet — checkpoint restores, kills and the rolling restart must
# stay invisible to scheduling truth, state-chaos (ISSUE 20) pins the
# anti-entropy contract — corruption quarantine and the device-loss
# ladder must leave the ledger byte-identical to a fault-free timeline
SCENARIOS = ((SCENARIO, CLIP_SECONDS), ("disruption-wave.yaml", 9000.0),
             ("service-fleet.yaml", 7200.0), ("state-chaos.yaml", 2400.0))

# report sections whose KEYS are data (shape classes seen, event kinds
# applied, ...): compared as opaque "dict" leaves, not recursed — their
# contents are pinned by the ledger digest where deterministic
_OPAQUE = {"events_applied", "fallbacks.classes", "attribution", "final"}


def report_shape(obj, prefix: str = "") -> list:
    """Sorted dotted key paths with value-type names — the report's
    structural fingerprint, value-free."""
    out = []
    if isinstance(obj, dict):
        if prefix.rstrip(".") in _OPAQUE:
            return [f"{prefix.rstrip('.')}:dict"]
        for k in sorted(obj):
            out.extend(report_shape(obj[k], f"{prefix}{k}."))
        return out
    path = prefix.rstrip(".")
    if isinstance(obj, list):
        return [f"{path}:list"]
    if isinstance(obj, bool):
        return [f"{path}:bool"]
    if isinstance(obj, (int, float)):
        return [f"{path}:number"]
    if obj is None:
        return [f"{path}:null"]
    return [f"{path}:str"]


def run_clipped(clip_seconds: float = CLIP_SECONDS,
                scenario: str = SCENARIO) -> dict:
    """One clipped deterministic run of a library scenario; returns the
    report dict (ledger digest included)."""
    import karpenter_tpu.sim as sim_pkg
    from karpenter_tpu.sim import FleetSimulator, load_scenario
    sc = load_scenario(os.path.join(os.path.dirname(sim_pkg.__file__),
                                    "scenarios", scenario))
    clip = min(clip_seconds, sc.duration)
    sc.events = [e for e in sc.events if e.at <= clip]
    sc.duration = clip
    return FleetSimulator(sc).run()


def current_pin(clip_seconds: float = CLIP_SECONDS,
                scenario: str = SCENARIO) -> dict:
    report = run_clipped(clip_seconds, scenario)
    return {
        "scenario": scenario,
        "clip_seconds": clip_seconds,
        "ledger_digest": report["ledger_digest"],
        "ledger_entries": report["ledger_entries"],
        "report_shape": report_shape(report),
    }


def current_pins() -> dict:
    """Every pinned scenario's clipped pin (the golden's v2 shape)."""
    return {"pins": [current_pin(clip, scenario)
                     for scenario, clip in SCENARIOS]}


def _golden_pins(golden: dict) -> list:
    """v2 golden ({"pins": [...]}) or the legacy single-pin dict."""
    return golden["pins"] if "pins" in golden else [golden]


def compare(pin: dict, golden: dict) -> list:
    """Human-readable mismatch lines ([] = green). Accepts either one
    pin vs one golden entry, or the v2 multi-scenario shapes."""
    if "pins" in pin or "pins" in golden:
        cur = {p["scenario"]: p for p in _golden_pins(pin)}
        want = {p["scenario"]: p for p in _golden_pins(golden)}
        problems = []
        for name in sorted(set(cur) | set(want)):
            if name not in want:
                problems.append(
                    f"scenario {name!r} has no golden pin — regenerate")
            elif name not in cur:
                problems.append(
                    f"pinned scenario {name!r} no longer runs — regenerate")
            else:
                problems.extend(f"[{name}] {p}"
                                for p in compare(cur[name], want[name]))
        return problems
    problems = []
    if pin["ledger_digest"] != golden["ledger_digest"]:
        problems.append(
            f"ledger digest changed:\n  pinned  {golden['ledger_digest']}"
            f"\n  current {pin['ledger_digest']}\n  (entries: pinned "
            f"{golden['ledger_entries']}, current {pin['ledger_entries']})")
    missing = sorted(set(golden["report_shape"]) - set(pin["report_shape"]))
    added = sorted(set(pin["report_shape"]) - set(golden["report_shape"]))
    if missing:
        problems.append("report keys GONE vs golden: " + ", ".join(missing))
    if added:
        problems.append("report keys NEW vs golden: " + ", ".join(added))
    return problems


def main(argv=None, pin: dict = None) -> int:
    """CLI gate; `pin` injects a precomputed current_pin() (the tier-1
    wrapper computes the ~2s clipped replay once and reuses it across its
    tests instead of re-running per invocation)."""
    parser = argparse.ArgumentParser(
        prog="python tools/sim_regression.py",
        description="sim ledger-digest + report-shape regression gate")
    parser.add_argument("--update", action="store_true",
                        help="regenerate the golden pin from this tree")
    parser.add_argument("--golden", default=GOLDEN_PATH,
                        help=f"golden file (default {GOLDEN_PATH})")
    args = parser.parse_args(argv)
    if pin is None:
        pin = current_pins()
    if args.update:
        os.makedirs(os.path.dirname(args.golden), exist_ok=True)
        with open(args.golden, "w") as f:
            json.dump(pin, f, indent=1, sort_keys=True)
            f.write("\n")
        lines = "\n".join(
            f"  [{p['scenario']}] ledger_digest {p['ledger_digest'][:16]}… "
            f"({p['ledger_entries']} entries, "
            f"{len(p['report_shape'])} report keys)"
            for p in _golden_pins(pin))
        print(f"golden updated: {args.golden}\n{lines}")
        return 0
    if not os.path.exists(args.golden):
        print(f"sim regression gate: no golden at {args.golden}\n"
              "  generate one: python tools/sim_regression.py --update",
              file=sys.stderr)
        return 2
    with open(args.golden) as f:
        golden = json.load(f)
    problems = compare(pin, golden)
    names = ", ".join(p["scenario"] for p in _golden_pins(golden))
    if problems:
        print("sim regression gate FAILED — the clipped "
              f"{names} replays diverged from the pin:\n"
              + "\n".join(f"- {p}" for p in problems)
              + "\n\nIf this behavior change is intentional, refresh the "
                "pin and commit it:\n    python tools/sim_regression.py "
                "--update", file=sys.stderr)
        return 1
    digests = " ".join(p["ledger_digest"][:16] + "…"
                       for p in _golden_pins(pin))
    print(f"sim regression gate green: digests {digests} match the pin")
    return 0


if __name__ == "__main__":
    sys.exit(main())
